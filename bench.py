"""Benchmark: flagship GPT training-step throughput on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu",
"dispatch_overhead_ms", "relay_degraded", "ledger_id", "config"}.
Every invocation also appends a structured record (git SHA, knob pins,
calibration, relay stamp) to benchmarks/ledger.jsonl via
apex_tpu.telemetry.ledger — "ledger_id" names it, so the headline
number can be traced back to exactly what was measured.

The measured program is the full apex-equivalent training step — bf16
forward/backward (amp O2 semantics), dynamic loss scaling, fused Adam —
on a GPT-2-small-shaped model, single chip.

Measurement method (see PERF.md for the calibration experiments): K steps
are chained inside ONE ``lax.scan`` under a single jit dispatch, and
completion is observed with a 1-element device fetch. On the axon-tunneled
TPU backend each dispatch costs ~65 ms of fixed relay latency and
``block_until_ready`` resolves before device execution finishes — a
per-step dispatch loop therefore measures the tunnel, not the chip (rounds
1-2 of this repo did exactly that, reporting ~7.6k tokens/s for a program
whose device time is ~20x faster). The measured per-dispatch overhead is
subtracted from the scan total.

``vs_baseline`` is the ratio against the recorded first-measurement
baseline in BENCH_BASELINE.json (created on first run; the reference repo
publishes no numbers to compare against — see BASELINE.md). The baseline
key is suffixed with the measurement method (``_scan``) — ratios against
the rounds-1/2 per-dispatch numbers would be method artifacts, not perf.
``mfu`` = model FLOPs (6*N*tokens) / step-time / chip bf16 peak.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# The measured-default TPU batch (PERF.md §10b): the one config whose
# scan survived the relay's large-program starvation mode. Shared by the
# env default and the baseline-seeding guard so a future measured flip
# cannot update one and orphan the other.
DEFAULT_TPU_BATCH = 8


def env_flag(name):
    """``=1`` knob gate via the one-home parser
    (apex_tpu.dispatch.tiles.env_flag), imported lazily: bench.py keeps
    apex_tpu out of module import time (the watchdog parses its
    environment before touching jax)."""
    from apex_tpu.dispatch.tiles import env_flag as _impl

    return _impl(name)

# Emergency-save staging (durability layer, ISSUE 6): after each scan
# boundary the inner run parks a HOST copy of the newest training state
# here — host copies, because the jit donates the device buffers into
# the next dispatch and a SIGTERM handler cannot fetch a donated array.
# The SIGTERM handler commits this through the DurableCheckpointer so a
# wedge-capped/terminated window still leaves a resumable checkpoint
# next to its best JSON line.
_EMERGENCY = {"writer": None, "state": None, "step": None, "meta": None,
              "platform": None}


def _stage_emergency(writer, step, state, meta, platform):
    """Fetch ``state`` to host and stage it for the SIGTERM flush. The
    fetch is the scan-boundary device→host transfer — it happens
    OUTSIDE the timed region (before timing starts / after it ends),
    so checkpoint cost can never leak into step cost."""
    import jax

    _EMERGENCY.update(state=jax.device_get(state), step=int(step),
                      meta=dict(meta), writer=writer, platform=platform)


def _emergency_sigterm(signum, frame):
    """Inner-run SIGTERM: commit the staged checkpoint and append a
    ``bench_emergency_save`` ledger record, then exit. Both terminate
    paths grant a 15 s grace window before SIGKILL (the watchdog's
    timeout path and its on_term handler) — enough for a host-side
    commit; a child wedged in native relay code never runs this, and
    the scan-boundary commit already banked the pre-wedge state (the
    commit protocol's atomicity keeps it the newest valid one).
    ``commit_now`` bypasses the async queue: a signal handler must not
    block on queue internals its interrupted frame may hold."""
    es = _EMERGENCY
    try:
        if es["writer"] is not None and es["state"] is not None:
            es["writer"].commit_now(es["step"], es["state"],
                                    meta=es["meta"])
            from apex_tpu.telemetry import ledger as _ledger

            _ledger.append_record(
                harness="bench_emergency_save", platform=es["platform"],
                dispatch_overhead_ms=None, k=None,
                extra={"terminated": "SIGTERM", "ckpt_step": es["step"],
                       "checkpoint": es["writer"].snapshot()})
            print(f"# emergency checkpoint committed at step "
                  f"{es['step']}", file=sys.stderr, flush=True)
    finally:
        os._exit(143)


def _default_batch(cfg, builtin, s):
    """The bench batch: APEX_BENCH_BATCH pins; else a dispatch-table
    "bench_batch" entry for this (s, hidden, layers) bucket — the cashed
    b-ladder A/B (benchmarks/autotune_steps.py) — else ``builtin``."""
    from apex_tpu import dispatch
    from apex_tpu.dispatch.tiles import env_int

    v = env_int("APEX_BENCH_BATCH")
    if v:
        return v

    choice = dispatch.lookup("bench_batch", dtype="bfloat16", s=s,
                             h=cfg.hidden_size, layers=cfg.num_layers)
    return int(choice) if choice else builtin


def _dispatch_snapshot():
    from apex_tpu import dispatch

    return dispatch.snapshot()


def _capture_step_cost(step, run, step_args, iters, model_flops_per_step,
                       platform, smoke=False, host_ms=None,
                       axis_sizes=None):
    """The attribution block for the measured K-step scan
    (apex_tpu.telemetry.costs): XLA-counted flops / HBM bytes / peak
    HBM + analytic floors, stamped into the JSON line and the ledger
    record so a headline MFU self-describes its gap.

    Pure host work off the timed path: ``step.lower`` and
    ``jax.make_jaxpr`` trace without dispatching anything, and
    ``memory_analysis`` (which needs a COMPILED executable) is taken
    only where the compile is a persistent-cache read or a CPU compile
    — never a second cold compile through the relay's remote-compile
    helper. Every failure degrades to None fields (the block is always
    stampable); ``APEX_COST_ANALYSIS=0`` skips the captures outright.
    """
    from apex_tpu import compile_cache
    from apex_tpu.telemetry import costs

    # smoke runs default the capture OFF (extra host traces for numbers
    # nobody cites — the ledger's smoke rule); APEX_COST_ANALYSIS=1/0
    # overrides either default
    if not costs.enabled(default=not smoke):
        return costs.null_block()
    lowered = compiled = None
    comm = None
    try:
        lowered = step.lower(*step_args)
    except Exception:
        pass
    try:
        if lowered is not None and (platform != "tpu"
                                    or compile_cache.enabled()):
            compiled = lowered.compile()
    except Exception:
        pass
    comm_compression = None
    comm_ms = None
    try:
        import jax

        # per-step comm: the scan body's collectives count once per
        # iteration, so divide the whole-program totals by the scan
        # length (comm_from_jaxpr multiplies scan bodies by length)
        total = costs.comm_from_jaxpr(jax.make_jaxpr(run)(*step_args))
        comm = {k: v / iters for k, v in total.items()}
        # the overlap_bound comm side (ROADMAP 4d, ISSUE 14): the
        # per-step payload over the measured-interconnect ENVELOPE —
        # size-1 axes move nothing on the wire (the single-chip tp
        # psums are traced but free), so they are filtered before the
        # claim, the same rule as minimal.training_comm_bytes
        comm_ms = costs.comm_ms_from_axis_bytes(
            costs.wire_bytes(comm, axis_sizes), platform)
        # comm-compression stamp (apex_tpu.parallel.collectives): when
        # the process-wide comm knobs are on, the measured program's
        # payload above is the COMPRESSED one — trace the uncompressed
        # twin (collectives.disabled(): preferences resolve off, the
        # program re-traces to the plain psum path) so the record
        # carries both sides of the payload claim
        from apex_tpu.parallel import collectives

        snap = collectives.snapshot()
        if snap.get("scheme") or snap.get("hierarchical"):
            with collectives.disabled():
                # fresh lambda: jax traces cache by function identity,
                # and the twin must RE-trace under the disabled knobs
                twin = costs.comm_from_jaxpr(
                    jax.make_jaxpr(lambda *a: run(*a))(*step_args))
            comm_compression = costs.comm_compression_block(
                snap, {k: v / iters for k, v in twin.items()})
    except Exception:
        pass
    return costs.capture(lowered=lowered, compiled=compiled, steps=iters,
                         comm=comm,
                         model_flops_per_step=model_flops_per_step,
                         platform=platform,
                         comm_compression=comm_compression,
                         host_ms=host_ms, comm_ms=comm_ms)


def make_one_step(model, scaler, tx):
    """The flagship amp-O2 training step: bf16 fwd/bwd, dynamic loss
    scaling, fused Adam, skip-step selects.

    Module-level so tests/test_telemetry.py can assert the zero-cost
    telemetry rule directly on the measured program: with telemetry
    disabled the returned step traces to a jaxpr byte-identical to the
    uninstrumented step.

    Returns ``one_step(params, opt_state, scaler_state, ids, pos,
    labels) -> (params, opt_state, scaler_state, loss, aux)`` where
    ``aux`` is None (an empty pytree — adds nothing to the compiled
    program) with telemetry disabled, else the in-step scalar dict
    (loss / loss_scale / overflow / unskipped / grad_norm / grad_max)
    that rides the training scan's stacked outputs.
    """
    import jax
    import jax.numpy as jnp

    from apex_tpu import telemetry
    from apex_tpu.optimizers import grad_norm_stats

    def one_step(params, opt_state, scaler_state, ids, pos, labels):
        def loss_fn(p):
            per_tok = model.apply({"params": p}, ids, pos, None, labels)
            return jnp.mean(per_tok) * scaler_state.loss_scale

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads, found_inf = scaler.unscale(grads, scaler_state)
        new_scaler_state = scaler.update(scaler_state, found_inf)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        new_params = jax.tree_util.tree_map(
            lambda p, u: jnp.where(found_inf, p, p + u.astype(p.dtype)),
            params, updates)
        new_opt_state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(found_inf, old, new),
            new_opt_state, opt_state)
        unscaled_loss = loss / scaler_state.loss_scale
        aux = None
        if telemetry.enabled():  # trace-time branch: disabled is free
            aux = telemetry.collect(
                None, loss=unscaled_loss,
                **scaler.metrics(new_scaler_state),
                **grad_norm_stats(grads))
        return (new_params, new_opt_state, new_scaler_state,
                unscaled_loss, aux)

    return one_step


def _warm_bench_programs(programs, platform=None, cost_ctx=None):
    """APEX_WARM_ONLY=1 path: AOT-compile (never run) every program of
    the scored bench attempt, populating the persistent compile cache
    (apex_tpu.compile_cache) so the NEXT invocation — the driver-scored
    run — dispatches cached executables instead of compiling through
    the relay's remote-compile helper, the component that wedges first
    (PERF.md §10b). The heavy programs (the K-step scan and its
    timed-rebind variant) are LOWERED and COMPILED, never executed —
    but the caller has already RUN the init/opt-init programs to
    produce the concrete state passed here, because only concrete args
    reproduce the scored run's cache keys bit-for-bit. So a warm pass
    does dispatch the (small) init programs through the relay; what it
    never dispatches is the measured scan. Prints ONE JSON status line
    (this mode bypasses the watchdog; the measurement contract line is
    untouched)."""
    from apex_tpu import compile_cache
    from apex_tpu import telemetry

    results, compiled_by_name, failed = {}, {}, None
    for name, spec in programs.items():
        if callable(spec):
            # deferred program: built only once an earlier warm's
            # compiled object exists (the timed-rebind key needs the
            # step scan's output shardings)
            try:
                fn, args = spec(compiled_by_name)
            except Exception as e:
                results[name] = {"error":
                                 f"{type(e).__name__}: {str(e)[:200]}"}
                failed = name
                continue
        else:
            fn, args = spec
        try:
            from apex_tpu.telemetry import flight

            # flight beats (ISSUE 16): a warm pass compiles through the
            # relay's wedge-prone helper — exactly the flight a
            # supervisor needs phase visibility into
            flight.beat("compile_start", program=name)
            results[name], compiled_by_name[name] = \
                compile_cache.warm(fn, args)
            flight.beat("compile_done", program=name)
        except Exception as e:  # report, keep warming the rest
            results[name] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
            failed = name
            continue
        # harvest the attribution block for free: the warm already paid
        # for the Compiled object, so cost_analysis/memory_analysis are
        # a host-side read — the PREDICTED peak HBM reaches the window
        # driver before any measured dispatch, which is what lets §6
        # small-HBM-first ordering flag a starvation-doomed program
        # before it burns window minutes
        from apex_tpu.telemetry import costs

        ctx = cost_ctx or {}
        if costs.enabled(default=not ctx.get("smoke")):
            block = costs.capture(
                compiled=compiled_by_name[name],
                steps=ctx.get("steps") or 1,
                model_flops_per_step=ctx.get("model_flops", {}).get(name),
                platform=platform)
            results[name]["cost"] = block
            flag = costs.starvation(block.get("peak_hbm_bytes"), platform)
            if flag:
                results[name]["starvation"] = flag
    ledger_id = telemetry.ledger.append_record(
        harness="bench_warm", platform=platform, dispatch_overhead_ms=None,
        k=None, extra={"warm": results,
                       "compile_cache": compile_cache.snapshot()})
    print(json.dumps({
        "warm_only": True,
        "warm": results,
        "compile_cache": compile_cache.snapshot(),
        "ledger_id": ledger_id,
    }), flush=True)
    return 1 if failed else 0


def main():
    # fault hooks FIRST (apex_tpu.resilience.faults — no-ops unless the
    # test-only APEX_FAULT_PLAN is set): the backend-init hang and the
    # relay-init crash are failures that strike before any backend
    # import, so their injection points sit there too
    from apex_tpu import resilience
    from apex_tpu.resilience import faults
    from apex_tpu.telemetry import flight
    # flight recorder (ISSUE 16): host-side phase beats, no-ops unless
    # APEX_FLIGHT_DIR is set. proc_start BEFORE the fault hooks — a
    # scripted backend-init hang must leave a beat behind it, so the
    # supervisor can tell "spawned then wedged" from "never spawned".
    flight.beat("proc_start")
    faults.fire("backend_init")
    faults.fire("mid_attempt")

    # smoke_mode BEFORE any backend-touching import (_smoke.py contract);
    # it also activates the persistent compile cache (default ON for
    # real runs, OFF for smoke; APEX_COMPILE_CACHE=1/0 overrides)
    from benchmarks._smoke import smoke_mode
    smoke_mode("APEX_BENCH_SMOKE")  # force-CPU tiny sanity mode

    from apex_tpu import compile_cache

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.amp.scaler import LossScaler
    from apex_tpu.optimizers.fused_adam import fused_adam
    from apex_tpu.telemetry import costs
    from apex_tpu.transformer.parallel_state import TENSOR_AXIS
    from apex_tpu.transformer.testing import GPTModel, TransformerConfig

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    flight.beat("backend_init", platform=platform)

    # Kernel-dispatch knobs shared with benchmarks/profile_gpt.py
    # (benchmarks/_knobs.py): the measured winners (PERF.md §3/§4/§7)
    # can be adopted or A/B'd without editing the bench.
    from benchmarks._knobs import (apply_dispatch_knobs,
                                   fused_head_requested, remat_granularity)

    apply_dispatch_knobs()
    fused_head = fused_head_requested()
    remat = remat_granularity()

    # GPT-2 small shapes on TPU; tiny on CPU (local smoke)
    if on_tpu:
        cfg = TransformerConfig(
            hidden_size=768, num_layers=12, num_attention_heads=12,
            vocab_size=50304, max_position_embeddings=1024,
            hidden_dropout=0.0, attention_dropout=0.0, bf16=True,
            fused_lm_head=fused_head, recompute_granularity=remat)
        # b=8: the measured-deliverable choice (PERF.md §10b). In the
        # round-5 window the b=16 16-step scan was starved by the relay's
        # large-program degraded mode (2.09 s/step) in the same minutes
        # the b=8 program ran at device speed (80.16 ms/step, 38.7% MFU)
        # — the starvation threshold sits between the two working sets.
        # The watchdog ladder still tries b=16 as its upside attempt
        # (amortization argument); a fully-healthy window takes it.
        # APEX_BENCH_BATCH pins; unset, a dispatch-table "bench_batch"
        # entry (the cashed b=16 A/B, benchmarks/autotune_steps.py)
        # overrides the built-in measured default.
        b = _default_batch(cfg, DEFAULT_TPU_BATCH, s=1024)
        s, iters = 1024, 16
        # the ONE v5e roofline home (telemetry.costs): the measured MFU
        # and its record's cost block must divide by the same peak, or
        # check 6 flags arithmetic drift on every cited record
        peak_flops = costs.peak_flops_for("tpu")
    else:
        cfg = TransformerConfig(
            hidden_size=128, num_layers=2, num_attention_heads=4,
            vocab_size=512, max_position_embeddings=128,
            hidden_dropout=0.0, attention_dropout=0.0, bf16=True,
            fused_lm_head=fused_head,
            fused_lm_head_interpret=bool(fused_head),
            recompute_granularity=remat)
        # the CPU smoke honors the same batch knob/table so the b-rung
        # A/B (autotune_steps --smoke) can exercise the ladder locally
        b, s, iters = _default_batch(cfg, 2, s=128), 128, 3
        peak_flops = costs.peak_flops_for("cpu")  # None: no CPU envelope

    # §6 selective-starvation injection point: the relay's observed
    # degraded mode starves programs by working-set size, so the fault
    # matcher keys on the batch the attempt is about to build
    faults.fire("large_program", batch=b)

    model = GPTModel(cfg)
    mesh = Mesh(np.asarray(jax.devices()[:1]), (TENSOR_AXIS,))
    scaler = LossScaler()
    tx = fused_adam(learning_rate=1e-4)

    rs = np.random.RandomState(0)
    ids_np = rs.randint(0, cfg.vocab_size, (b, s))
    labels_np = rs.randint(0, cfg.vocab_size, (b, s))

    from benchmarks._timing import measure_dispatch_overhead, sync

    def shmap(f, n_in):
        return jax.shard_map(f, mesh=mesh, in_specs=(P(),) * n_in,
                             out_specs=P(), check_vma=False)

    init_fn = jax.jit(shmap(
        lambda ids, pos: model.init(jax.random.PRNGKey(0), ids, pos,
                                    None)["params"], 2))
    opt_init_fn = jax.jit(lambda p: tx.init(p))

    one_step = make_one_step(model, scaler, tx)

    def run(params, opt_state, scaler_state, eps, ids, pos, labels):
        def local(params, opt_state, scaler_state, eps, ids, pos, labels):
            def body(carry, _):
                p, o, ss = carry
                p, o, ss, loss, aux = one_step(p, o, ss, ids, pos, labels)
                return (p, o, ss), (loss, aux)

            (params, opt_state, scaler_state), (losses, aux) = lax.scan(
                body, (params, opt_state, scaler_state), jnp.arange(iters))
            # adding the traced eps (0 warm / 1e-30 timed) to the output
            # varies the call signature-values between warmup and timing,
            # defeating any same-args result caching in the relay; the
            # compute chain itself is kept live by the params carry
            return params, opt_state, scaler_state, losses + eps, aux

        return jax.shard_map(
            local, mesh=mesh, in_specs=(P(),) * 7, out_specs=P(),
            check_vma=False)(params, opt_state, scaler_state, eps, ids, pos,
                             labels)

    # donate params/opt/scaler state so XLA updates them in place across
    # the scan (the training-loop aliasing a real deployment would have)
    step = jax.jit(run, donate_argnums=(0, 1, 2))

    ids = jnp.asarray(ids_np, jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
    labels = jnp.asarray(labels_np, jnp.int32)
    params = init_fn(ids, pos)
    opt_state = opt_init_fn(params)
    scaler_state = scaler.init()

    if compile_cache.warm_only():
        # AOT warm path: the init/opt-init programs were just compiled
        # (and therefore cached) by running them above; the state they
        # produced carries the exact shardings the scored attempt's
        # arguments will carry, so lowering the remaining programs with
        # these CONCRETE args reproduces the scored run's cache keys
        # bit-for-bit (bare ShapeDtypeStruct avals do not — they drop
        # the arg shardings and the big scan misses). Nothing below is
        # executed or timed: compile only.
        from apex_tpu.telemetry.tracing import _overhead_program

        zero = jnp.float32(0.0)
        step_args = (params, opt_state, scaler_state, zero, ids, pos,
                     labels)

        def timed_rebind(compiled_by_name):
            # the TIMED dispatch rebinds the donated state to the first
            # call's OUTPUTS; on jax versions where output shardings
            # carry annotations the inputs lack (memory kinds), that is
            # a distinct cache key — and a cold compile INSIDE the
            # timed region. Reconstruct it from the warmed scan's
            # output shardings, no execution needed.
            compiled = compiled_by_name["step_scan"]
            out_avals = jax.eval_shape(step, *step_args)
            out_sds = jax.tree_util.tree_map(
                lambda aval, sh: jax.ShapeDtypeStruct(
                    aval.shape, aval.dtype, sharding=sh),
                out_avals, compiled.output_shardings)
            return step, (out_sds[0], out_sds[1], out_sds[2], zero,
                          ids, pos, labels)

        n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
        step_flops = 6.0 * n_params * b * s
        sys.exit(_warm_bench_programs({
            "dispatch_overhead": (_overhead_program(iters), (zero, zero)),
            "step_scan": (step, step_args),
            "step_scan_timed_rebind": timed_rebind,
        }, platform=platform, cost_ctx={
            "steps": iters,
            "smoke": env_flag("APEX_BENCH_SMOKE"),
            "model_flops": {"step_scan": step_flops,
                            "step_scan_timed_rebind": step_flops},
        }))

    # ------------------------------------------------- durability layer
    # (opt-in: APEX_CKPT_DIR; ISSUE 6). Restore happens HERE — before
    # the overhead calibration and the warm scan — so restore cost can
    # never mix into step cost; the provenance stamped below makes that
    # mechanically checkable (check_bench_labels check 5).
    from apex_tpu.telemetry import ledger as tledger

    ckpt_writer, resumed_from, step0 = None, None, 0
    rng = jax.random.PRNGKey(0)
    if os.environ.get("APEX_CKPT_DIR"):
        import signal

        from apex_tpu import checkpoint as ckpt_mod

        ckpt_writer = ckpt_mod.DurableCheckpointer(
            os.environ["APEX_CKPT_DIR"])
        if env_flag("APEX_CKPT_RESUME"):
            tmpl = {"params": params, "opt": opt_state,
                    "scaler": scaler_state, "rng": rng}
            # the batch/seq guard matters because the state TREE is
            # batch-independent — only the saved meta can refuse a
            # cross-config resume (checkpoint.resume_provenance is the
            # one implementation, shared with profile_gpt)
            restored, step0, resumed_from = ckpt_mod.resume_provenance(
                ckpt_writer, tmpl, expect_meta={"batch": b, "s": s})
            if restored is not None:
                params, opt_state = restored["params"], restored["opt"]
                scaler_state, rng = restored["scaler"], restored["rng"]
            else:
                print("# resume requested but no usable checkpoint in "
                      f"{ckpt_writer.directory}; cold start",
                      file=sys.stderr, flush=True)

        def ckpt_meta(step):
            return {"step": int(step), "harness": "bench", "batch": b,
                    "s": s, "knob_pins": tledger.measurement_pins()}

        # stage the post-init/restore state and arm the SIGTERM flush:
        # from here on, a terminated attempt leaves a checkpoint
        _stage_emergency(ckpt_writer, step0,
                         {"params": params, "opt": opt_state,
                          "scaler": scaler_state, "rng": rng},
                         ckpt_meta(step0), platform)
        signal.signal(signal.SIGTERM, _emergency_sigterm)

    overhead = measure_dispatch_overhead(iters)
    # calibration-flap injection point: a relay flap straddling the
    # calibration inflates the measured overhead relative to the timed
    # scan — the recorded round-4 "non-positive step time" mode
    overhead = faults.transform("calibration_overhead", overhead)

    # remote-compile failure injection point: the relay's remote-compile
    # helper returns HTTP 500 on oversized configs and is the component
    # that wedges first (PERF.md §6/§10b)
    faults.fire("compile", batch=b)

    # attribution capture BEFORE the warm dispatch: the jit donates the
    # state buffers into the scan, so this is the last point the
    # concrete args (whose shardings reproduce the warmed cache key)
    # are alive — and strictly before t0, so nothing here can leak
    # into the timed region
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    model_flops_per_step = 6.0 * n_params * b * s
    # the overlap_bound host side (ROADMAP 4a/4d, ISSUE 14): the
    # measured host→device staging wall of ONE batch — the per-step
    # cost a synchronous feed serializes and APEX_PREFETCH hides
    # (apex_tpu.overlap.prefetch). Measured HERE, strictly before the
    # warm dispatch and t0, so the extra round trips can never leak
    # into the timed region; smoke runs skip it with the rest of the
    # capture (the ledger smoke rule).
    host_stage_ms = None
    if costs.enabled(default=not env_flag("APEX_BENCH_SMOKE")):
        from apex_tpu.overlap import prefetch as prefetch_mod

        try:
            # stage exactly what a per-step feed moves: the int32
            # ids/labels tensors (rs.randint yields int64 — staging
            # those would claim ~2x the real bytes; pos is
            # loop-invariant, a feed never re-stages it)
            host_stage_ms = prefetch_mod.staging_seconds(
                (ids_np.astype(np.int32),
                 labels_np.astype(np.int32))) * 1e3
        except Exception:
            host_stage_ms = None
    cost_block = _capture_step_cost(
        step, run, (params, opt_state, scaler_state, jnp.float32(0.0),
                    ids, pos, labels),
        iters, model_flops_per_step, platform,
        smoke=env_flag("APEX_BENCH_SMOKE"), host_ms=host_stage_ms,
        axis_sizes=dict(zip(mesh.axis_names, mesh.devices.shape)))

    # compile + warm + drain (donated inputs: rebind the carried state)
    print(f"# compiling {iters}-step scan at b={b} s={s} ...",
          file=sys.stderr, flush=True)
    flight.beat("compile_start", batch=b)
    params, opt_state, scaler_state, losses, _ = step(
        params, opt_state, scaler_state, jnp.float32(0.0), ids, pos, labels)
    sync(losses)
    flight.beat("compile_done", batch=b)
    if ckpt_writer is not None:
        # scan boundary 1: host-stage AND COMMIT the warm scan's output
        # (the device buffers are about to be donated into the timed
        # dispatch). The commit is host-side and strictly before t0,
        # so no checkpoint cost can leak into the timed region — and a
        # child hard-wedged in the timed dispatch (the mode that never
        # runs its SIGTERM handler) still leaves this state banked.
        _stage_emergency(ckpt_writer, step0 + iters,
                         {"params": params, "opt": opt_state,
                          "scaler": scaler_state, "rng": rng},
                         ckpt_meta(step0 + iters), platform)
        ckpt_writer.save(step0 + iters, _EMERGENCY["state"],
                         meta=_EMERGENCY["meta"])
        ckpt_writer.flush()

    # chaos site (ISSUE 16): the heartbeat-silent wedge — beats were
    # flowing (proc_start..compile_done above), then the process goes
    # quiet with the scan-boundary-1 partial already committed. The
    # flight_watch supervisor must reap it at the silence threshold
    # (SIGTERM -> the emergency flush banks the partial) instead of
    # burning the full rung slot.
    faults.fire("flight_silent", batch=b)

    from apex_tpu.telemetry import profiling

    if profiling.capture_active():
        # profiler-capture child (APEX_PROFILE_INNER=1 — spawned by the
        # watchdog hook AFTER the scored attempts, never the scored
        # attempt itself): trace K' post-warmup steps (the scan above
        # was the warmup) and stamp the artifact + its content hash
        # into the ledger. A traced run is perturbed by its own
        # instrumentation, so no value/baseline/measurement comes out
        # of this path — harness "bench_profile", one JSON status line.
        from apex_tpu import telemetry

        reason = profiling.refusal()
        if reason is not None:
            print(json.dumps({"profile_only": True, "profile": None,
                              "error": f"profile capture refused: "
                                       f"{reason}"}), flush=True)
            return
        outdir = profiling.new_capture_dir(f"bench-{platform}-b{b}")
        with profiling.trace(outdir) as traced:
            out = step(params, opt_state, scaler_state,
                       jnp.float32(1e-30), ids, pos, labels)
            sync(out[3])
        art = profiling.artifact_block(outdir)
        ledger_id = telemetry.ledger.append_record(
            harness="bench_profile", platform=platform,
            dispatch_overhead_ms=round(overhead * 1e3, 1), k=iters,
            extra={"profile": art, "cost": cost_block,
                   "compile_cache": compile_cache.snapshot(),
                   "config": {"batch": b, "s": s}})
        print(json.dumps({"profile_only": True, "traced": bool(traced),
                          "k": iters, "profile": art,
                          "ledger_id": ledger_id}), flush=True)
        return

    print("# compiled; timing", file=sys.stderr, flush=True)
    # dispatch/fetch beats strictly OUTSIDE the timed region (before t0
    # / after dt's perf_counter read): the §0 measurement is unchanged
    flight.beat("dispatch", batch=b)
    t0 = time.perf_counter()
    out = step(params, opt_state, scaler_state, jnp.float32(1e-30), ids, pos,
               labels)
    sync(out[3])
    dt = (time.perf_counter() - t0 - overhead) / iters
    flight.beat("fetch", batch=b)

    final_step = step0 + 2 * iters
    if ckpt_writer is not None:
        # scan boundary 2 (timing closed): commit the final TrainState.
        # The final_save fault site models a wedge striking exactly
        # here — the emergency SIGTERM path must still flush.
        faults.fire("final_save")
        _stage_emergency(ckpt_writer, final_step,
                         {"params": out[0], "opt": out[1],
                          "scaler": out[2], "rng": rng},
                         ckpt_meta(final_step), platform)
        ckpt_writer.save(final_step, _EMERGENCY["state"],
                         meta=_EMERGENCY["meta"])
        ckpt_writer.flush()

    from apex_tpu import telemetry

    def ledger_record(degraded, kind, **extra):
        # every invocation — including an unusable one — lands in the
        # run ledger; a window's failures are evidence too (§6). The
        # compile_cache block proves whether the number was compile-free.
        from apex_tpu import dispatch as dispatch_table

        base = {"metric": f"gpt2s_train_tokens_per_sec ({platform})",
                "compile_cache": compile_cache.snapshot(),
                "dispatch": dispatch_table.snapshot(),
                # the attribution block (apex_tpu.telemetry.costs):
                # XLA-counted flops/bytes/peak-HBM + analytic floors —
                # check_bench_labels check 6 polices MFU arithmetic
                # against it on cited records
                "cost": cost_block}
        if ckpt_writer is not None:
            base["checkpoint"] = ckpt_writer.snapshot()
        if resumed_from is not None:
            # resume provenance INSIDE the content-hashed record id:
            # a timing row that restored state self-describes its
            # lineage tamper-evidently (check_bench_labels check 5
            # pin-matches citations of resumed records)
            base["resumed_from"] = resumed_from
        return telemetry.ledger.append_record(
            harness="bench", platform=platform,
            dispatch_overhead_ms=round(overhead * 1e3, 1), k=iters,
            relay={"degraded": degraded, "kind": kind},
            extra=dict(base, **extra))

    if dt <= 0:
        # the dispatch-overhead calibration ran in a slower relay regime
        # than the timed scan (the relay flaps) — the subtraction went
        # negative and no throughput can be derived from this run
        flap = {
            "metric": f"gpt2s_train_tokens_per_sec ({platform})",
            "value": 0, "unit": "tokens/s", "vs_baseline": 0, "mfu": None,
            "dispatch_overhead_ms": round(overhead * 1e3, 1),
            "relay_degraded": True,
            "compile_cache": compile_cache.snapshot(),
            "cost": cost_block,
            "ledger_id": ledger_record(True, "calibration-flap", value=0),
            "error": "non-positive step time after overhead subtraction "
                     "(relay flap straddled the calibration); "
                     "measurement unusable"}
        if faults.plan_hash():
            flap["fault_plan"] = faults.plan_hash()
        print(faults.transform_output(json.dumps(flap)), flush=True)
        return

    tokens_per_sec = b * s / dt
    mfu = None
    if peak_flops:
        mfu = round(model_flops_per_step / dt / peak_flops, 4)

    # The MFU-envelope degradation verdict (thresholds and their
    # PERF.md §1/§6 calibration live in apex_tpu.resilience — the one
    # classifier the watchdog, the probe CLI and autotune share): <5%
    # MFU on TPU at MXU-feeding batches = relay-dominated; >60% =
    # implausible calibration straddle. A fault plan can inject the
    # verdict deterministically (the record is fault-stamped below).
    degraded_kind = resilience.classify_measurement(
        on_tpu=on_tpu, mfu=mfu, batch=b)
    implausible = degraded_kind == "implausible"
    degraded = degraded_kind is not None

    # APEX_BENCH_BASELINE redirects the baseline store (chaos tests
    # exercise the seeding gate without touching the committed series)
    baseline_path = os.environ.get("APEX_BENCH_BASELINE") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    # the unqualified key is the DEFAULT-batch series; a non-default TPU
    # batch (the ladder's b=16 upside, APEX_BENCH_BATCH overrides) gets
    # its own _b{N}-suffixed series — cross-batch ratios would measure
    # amortization, not performance, the same class of method artifact
    # the _scan/_per-dispatch split guards against
    key = f"gpt_tokens_per_sec_{platform}_scan"
    if on_tpu and b != DEFAULT_TPU_BATCH:
        key += f"_b{b}"
    baselines = {}
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baselines = json.load(f)
    if key not in baselines and not degraded and (not on_tpu or b >= 8):
        # never seed any series' baseline from a degraded-relay run, nor
        # from a sub-calibration TPU batch (b < 8) the degraded detector
        # is blind to (the CPU smoke's fixed b=2 self-seeds as before)
        baselines[key] = tokens_per_sec
        with open(baseline_path, "w") as f:
            json.dump(baselines, f, indent=1)
    # no recorded baseline (degraded run refused to seed one): report 0,
    # the same "not comparable" sentinel the watchdog's error line uses
    vs_baseline = tokens_per_sec / baselines[key] if key in baselines else 0.0

    config = {
        "batch": b,
        # sequence length rides the label so check_bench_labels check 6
        # can recompute MFU from the cost block's flops (tokens = b*s)
        "s": s,
        # knob PINS, tri-state: True/False (or a string value) = pinned,
        # None = unpinned — resolved by the dispatch table at trace
        # time; the resolved choices are in the JSON line's "dispatch"
        # consult log, so the label stays mechanical either way
        "fused_lm_head": fused_head,
        "attn_impl": os.environ.get("APEX_ATTN_IMPL"),
        "ln_pallas": (os.environ.get("APEX_LN_PALLAS") == "1"
                      if os.environ.get("APEX_LN_PALLAS") in ("0", "1")
                      else None),
        "remat": remat,
        # telemetry-on measures the INSTRUMENTED program (aux outputs in
        # the timed scan) — the label must say so (pin-the-label rule);
        # the default-off path is jaxpr-identical to uninstrumented
        "telemetry": bool(telemetry.enabled()),
    }
    ledger_id = ledger_record(
        bool(degraded), degraded_kind, value=round(tokens_per_sec, 1),
        unit="tokens/s", mfu=mfu, config=config)
    result = {
        "metric": f"gpt2s_train_tokens_per_sec ({platform})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
        "mfu": mfu,
        "dispatch_overhead_ms": round(overhead * 1e3, 1),
        "relay_degraded": bool(degraded),
        # whether this number was served from the persistent compile
        # cache (warm-start subsystem) — misses on a warmed window mean
        # the warm drifted from the measured program
        "compile_cache": compile_cache.snapshot(),
        "ledger_id": ledger_id,
        # the active kernel dispatch, so a watchdog-selected best line
        # self-describes (the ladder A/Bs configs across attempts)
        "config": config,
        # which dispatch-table entries resolved this run's unpinned
        # choices (apex_tpu.dispatch consult log) — the data-driven half
        # of the pin-the-label rule
        "dispatch": _dispatch_snapshot(),
        # the attribution block: what the step SHOULD cost (XLA flops /
        # HBM bytes / peak HBM, analytic floors, MFU bound) next to
        # what it measured — null-degraded where the backend (or the
        # smoke default) reported nothing
        "cost": cost_block,
    }
    if ckpt_writer is not None:
        # the durability telemetry block: {saves, queue_depth,
        # commit_ms, last_step} (+async/errors) — a window's driver log
        # proves whether its checkpoints committed
        result["checkpoint"] = ckpt_writer.snapshot()
        ckpt_writer.close()
    if resumed_from is not None:
        result["resumed_from"] = resumed_from
    if faults.plan_hash():
        # a run under fault injection is stamped in the line itself (the
        # ledger record carries the stamp inside its content-hashed id):
        # an injected run can never masquerade as a measurement
        result["fault_plan"] = faults.plan_hash()
    if telemetry.enabled():
        # flush the in-step scalars (stacked by the timed scan) + the
        # host-derived throughput to the metrics sink — AFTER the timed
        # region, fetched with plain np.asarray (no callbacks)
        try:
            stacked = {k_: np.asarray(v) for k_, v in out[4].items()}
            writer = telemetry.MetricsWriter()
            writer.append_steps(stacked, run=ledger_id)
            writer.append({"run": ledger_id,
                           "tokens_per_sec": round(tokens_per_sec, 1)})
        except Exception as e:  # never break the one-JSON-line contract
            print(f"# telemetry metrics write failed: {e}",
                  file=sys.stderr, flush=True)
    if degraded:
        # structured kind alongside the prose note: the watchdog's
        # best-selection tiers on this, never on the wording
        result["degraded_kind"] = degraded_kind
        result["note"] = (
            "implausible MFU — the relay flap straddled the dispatch-"
            "overhead calibration and inflated the number; unreliable"
            if implausible else
            "TPU relay degraded during this run (per-step time far outside "
            "the device envelope measured in PERF.md §1: 82.5 ms/step, "
            "37.6% MFU at b=8); value reflects tunnel latency, not the chip")
    # emit-site faults model the wedging-teardown truncation of the one
    # JSON line (no-op without APEX_FAULT_PLAN)
    flight.beat("flush", batch=b)
    print(faults.transform_output(json.dumps(result)), flush=True)


def _last_json(text):
    """(line, record) of the last PARSEABLE JSON line in *text* —
    delegates to apex_tpu.resilience.last_json, the one scanner behind
    the watchdog, the timeout path, the collection gate and the probe
    CLI."""
    from apex_tpu import resilience

    return resilience.last_json(text)


def _requested_backend(rec, smoke=False):
    """Delegates to apex_tpu.resilience.requested_backend — the guard
    keeping silent-CPU-fallback numbers out of the headline."""
    from apex_tpu import resilience

    return resilience.requested_backend(rec, smoke)


def _healthy_record(rec, smoke=False):
    """Delegates to apex_tpu.resilience.healthy — the single health
    classifier behind the watchdog's stop condition, the probe CLI, and
    benchmarks/probe_and_collect.sh's collection gate."""
    from apex_tpu import resilience

    return resilience.healthy(rec, smoke=smoke)


def _healthy_json_line(text, smoke=False):
    """The last JSON record of *text* when `_healthy_record` accepts it,
    else None."""
    _, rec = _last_json(text)
    return rec if rec is not None and _healthy_record(rec, smoke) else None


def _config_ladder(attempts, smoke):
    """Per-attempt extra-env configs. Unless the caller pinned a dispatch
    knob or the batch (explicit request — honored verbatim on every
    attempt), the ladder A/Bs the batch amortization upside: attempt 1 =
    defaults (b=8, the config measured to survive the relay's
    large-program starvation mode — PERF.md §10b), attempt 2 = b=16,
    further attempts = defaults (flap retries). The watchdog's
    healthy-first, then highest-throughput ranking makes the driver run
    double as the A/B — the best line's ``config`` field says which
    batch won. (The fused-LM-head step A/B moved to the collection
    pass's profile_gpt rung after the §10b kernel-level measurement put
    it 37% behind on throughput.)"""
    pinned = any(os.environ.get(k)
                 for k in ("APEX_FUSED_LM_HEAD", "APEX_ATTN_IMPL",
                           "APEX_LN_PALLAS", "APEX_REMAT",
                           "APEX_BENCH_BATCH"))
    if smoke or pinned or attempts < 2:
        return [{}] * attempts
    # the b=16 upside attempt opts OUT of the durability layer (None =
    # unset in _attempt_once): resuming a default-config checkpoint
    # under a different batch pin would stamp pin_drift provenance and
    # make the A/B line uncitable (check 5), and its final save would
    # park a b=16-trajectory state where the default config resumes —
    # only the default config banks durable state
    return [{}, {"APEX_BENCH_BATCH": "16", "APEX_CKPT_DIR": None,
                 "APEX_CKPT_RESUME": None}] + [{}] * (attempts - 2)


def _attempt_once(state, extra_env=None, timeout_cap=None, attempt=0):
    """One watchdogged run of main() in a subprocess.

    Returns ``(line, record, returncode_or_None)`` — line and record are
    None when the child produced no parseable JSON (only possible for a
    crash: the timeout path always fabricates an error record, stamped
    ``"timed_out": true``, and returns returncode None). A wedged
    TPU relay — observed round 3, even backend init hangs, PERF.md §6 —
    must produce an honest error line, not hang the caller forever, so
    the child gets a hard timeout. ``timeout_cap`` shortens that budget;
    the watchdog arms it after an earlier attempt rode its ENTIRE
    timeout without printing a JSON line (the wedge signature — there is
    no init pre-flight, the evidence is always a prior attempt). The
    live Popen handle is parked in ``state["child"]`` so the SIGTERM
    handler can take down exactly the in-flight attempt (not the whole
    process group, which may be shared with a supervising driver).

    This is the subprocess boundary the fault-injection layer is
    honored across: ``APEX_FAULT_PLAN`` rides the inherited env into
    the child (where main()'s hook points fire), and the attempt index
    is exported as ``APEX_BENCH_ATTEMPT`` so a fault plan can script a
    per-attempt window timeline (``match_env``).
    """
    import subprocess

    from apex_tpu import resilience

    env = dict(os.environ, APEX_BENCH_INNER="1",
               APEX_BENCH_ATTEMPT=str(attempt))
    for k, v in (extra_env or {}).items():
        # None UNSETS the var (the ladder's durability opt-out) — the
        # same semantics autotune/warm_cache subprocess envs use
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    timeout = resilience.attempt_timeout(timeout_cap)
    label = "cpu" if env_flag("APEX_BENCH_SMOKE") else "tpu"

    # capture stdout (the JSON line) only; stderr is inherited so the
    # '# compiling ...' liveness prints stream during the slow compile
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            env=env, stdout=subprocess.PIPE, text=True)
    state["child"] = proc
    try:
        out, _ = proc.communicate(timeout=timeout)
        line, rec = _last_json(out)
        return line, rec, proc.returncode
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
        # the child may have printed its result and then wedged in
        # backend teardown — forward a completed JSON line over nothing
        line, rec = _last_json(out)
        if rec is not None:
            return line, rec, None
        # structured wedge marker (resilience.timeout_record stamps
        # "timed_out": the lazy-cap arming keys on THIS, never on the
        # error wording — a real error record forwarded after a
        # teardown wedge must not arm the cap)
        rec = resilience.timeout_record(label, timeout)
        return json.dumps(rec), rec, None
    finally:
        state["child"] = None


def _maybe_profile_capture(state):
    """The watchdog's APEX_PROFILE_CAPTURE=1 hook: after the scored
    attempts (and after the one JSON line is flushed — stdout stays the
    driver's), run ONE profiler-capture child under the resilience
    timeout envelope. Refused under APEX_FAULT_PLAN; skipped when no
    attempt completed a real measurement this window (a wedged relay
    should not be handed another 900s program). All reporting goes to
    stderr; the child's ledger record carries the artifact stamp."""
    import subprocess

    from apex_tpu.telemetry import profiling

    if not profiling.requested():
        return
    reason = profiling.refusal()
    if reason is not None:
        print(f"# profile capture REFUSED: {reason}", file=sys.stderr,
              flush=True)
        return
    pair = state["best"]
    if pair is None or "error" in pair[1]:
        print("# profile capture skipped: no completed measurement this "
              "window", file=sys.stderr, flush=True)
        return
    timeout = profiling.timeout_s()
    print(f"# profile capture: tracing post-warmup steps in a subprocess "
          f"(timeout {timeout}s)", file=sys.stderr, flush=True)
    env = dict(os.environ, APEX_BENCH_INNER="1", APEX_PROFILE_INNER="1")
    # re-apply the WINNING attempt's ladder env (same None-unsets
    # semantics as _attempt_once) so the trace profiles the program the
    # headline line measured — e.g. when the b=16 upside attempt won,
    # the capture must not quietly trace the default b=8 shape
    for k, v in (state.get("best_env") or {}).items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    try:
        # Popen + state["child"] (not subprocess.run): the watchdog's
        # SIGTERM handler kills exactly state["child"] — a capture
        # child blocked through the relay must be reaped by the slot
        # timeout like any attempt, never orphaned holding the device
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, text=True)
        state["child"] = proc
        out, _ = proc.communicate(timeout=timeout)
        _, rec = _last_json(out)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        print(f"# profile capture timed out after {timeout}s (wedge "
              "signature) — artifact abandoned", file=sys.stderr,
              flush=True)
        return
    except OSError as e:
        print(f"# profile capture failed to launch: {e}", file=sys.stderr,
              flush=True)
        return
    finally:
        state["child"] = None
    if rec and rec.get("profile"):
        art = rec["profile"]
        print(f"# profile capture: {art.get('files')} file(s), "
              f"{art.get('bytes')} bytes in {art.get('dir')} "
              f"(sha256 {str(art.get('sha256'))[:12]}..., "
              f"ledger {rec.get('ledger_id')})", file=sys.stderr,
              flush=True)
    else:
        print(f"# profile capture produced no artifact "
              f"({(rec or {}).get('error', f'rc={proc.returncode}')})",
              file=sys.stderr, flush=True)


def _watchdog():
    """Retry through relay flaps, report the best attempt.

    The round-3 relay alternates between healthy, degraded (~40x slow),
    and wedged within minutes (PERF.md §6) — one unlucky attempt must not
    be the recorded number. Attempts walk the ``_config_ladder`` (the
    b=16 amortization A/B rides the retries; each line's ``config``
    field says what it measured) and stop once every distinct config has
    a healthy run (no 'note'/'error') on the requested backend;
    otherwise the highest-throughput line is printed, falling back to a
    cpu-fallback or error line when nothing better exists. A child crash (non-zero
    exit, no JSON) is retried too — relay-init failures can crash
    instead of hang — but with a short wait, so a deterministic crash
    (e.g. an import error, whose traceback already streamed on stderr)
    re-fails in seconds rather than burning the relay-flap backoff.

    Exactly ONE JSON line goes to stdout. If an outer timeout kills us
    mid-retry (run_all_tpu.sh budgets bench generously, but the driver's
    budget is unknown), the SIGTERM handler flushes the best line seen so
    far — plus a ``bench_watchdog`` ledger record, so a terminated
    window leaves evidence — instead of dying silently and discarding
    every measurement. Returns 0 when a real measurement (healthy or
    degraded) was produced on the requested backend; the child's exit
    code when every attempt crashed; 1 otherwise.

    Classification (healthy / degraded / implausible tiers), the retry
    pacing and the lazy wedge cap are apex_tpu.resilience — the single
    implementation shared with the probe CLI and autotune.
    """
    import signal

    from apex_tpu import resilience
    # imported HERE, not inside the signal handler: the import machinery
    # must never run under a mid-import SIGTERM
    from apex_tpu.telemetry import flight as _flight
    from apex_tpu.telemetry import ledger as _tledger
    _ckpt_mod = None
    if os.environ.get("APEX_CKPT_DIR"):
        from apex_tpu import checkpoint as _ckpt_mod

    policy = resilience.RetryPolicy()
    attempts = policy.attempts
    smoke = env_flag("APEX_BENCH_SMOKE")
    # "best"/"fallback" hold (line, record) pairs; best_rank orders
    # candidates as (healthy?, value) so a healthy measurement always
    # beats a degraded/implausible one regardless of its (possibly
    # inflated) tokens/s value
    state = {"best": None, "best_rank": (-1, -1.0), "best_env": None,
             "fallback": None, "printed": False, "child": None}

    def flush_best():
        if state["printed"]:
            return
        state["printed"] = True
        pair = state["best"] or state["fallback"]
        label = "cpu" if smoke else "tpu"
        print(pair[0] if pair is not None else json.dumps({
            "metric": f"gpt2s_train_tokens_per_sec ({label})",
            "value": 0, "unit": "tokens/s", "vs_baseline": 0, "mfu": None,
            "error": "all bench attempts failed to produce a JSON line"}),
            flush=True)

    def ok_rc():
        # 0 only for a real measurement (healthy or degraded) on the
        # requested backend — a cpu-fallback or error line is a failure
        pair = state["best"] or state["fallback"]
        if pair is None:
            return 1
        rec = pair[1]
        return 0 if ("error" not in rec
                     and _requested_backend(rec, smoke)) else 1

    def on_term(signum, frame):
        flush_best()
        # a terminated window is evidence too: record what was flushed
        # — and, when the durability layer is armed, the newest
        # committed checkpoint on disk, so the next window knows what
        # `--resume` will pick up (never raises; smoke runs skip the
        # write unless APEX_TELEMETRY_LEDGER is set — the ledger's rule)
        pair = state["best"] or state["fallback"]
        extra = {"terminated": "SIGTERM",
                 "flushed": pair[1] if pair is not None else None}
        child = state["child"]
        if os.environ.get("APEX_CKPT_DIR") and child is not None:
            # give a LIVE child its emergency-save grace: SIGTERM, then
            # a bounded wait (15 s — the same grace the timeout path
            # grants, sized for a host-side commit of the full
            # TrainState). A child wedged in native relay code ignores
            # it and eats the SIGKILL below, exactly as before.
            try:
                child.terminate()
                child.wait(timeout=15)
            except Exception:
                pass
        if _ckpt_mod is not None:
            # the on-disk peek (NOT the writer's telemetry block —
            # that schema belongs to the inner run): what --resume
            # will pick up next window
            try:
                m = _ckpt_mod.latest_durable_manifest(
                    os.environ["APEX_CKPT_DIR"])
                extra["ckpt_on_disk"] = (
                    {"last_step": m["step"], "id": m.get("id")}
                    if m else None)
            except Exception:
                extra["ckpt_on_disk"] = None
        _tledger.append_record(
            harness="bench_watchdog",
            platform="cpu" if smoke else "tpu",
            dispatch_overhead_ms=None, k=None,
            extra=extra)
        if child is not None:
            # SIGKILL, not SIGTERM: the observed wedge is a child stuck
            # in native relay code that never runs Python signal
            # handling, and this handler cannot wait around to escalate
            # — an orphaned wedged child would keep the device busy for
            # every subsequent harness in a collection pass
            try:
                child.kill()
            except OSError:
                pass
        os._exit(ok_rc())

    signal.signal(signal.SIGTERM, on_term)

    ladder = _config_ladder(attempts, smoke)
    distinct = {json.dumps(c, sort_keys=True) for c in ladder}
    healthy_configs = set()
    last_outcome = "relay-bound"
    # Lazy wedge cap (resilience.RetryPolicy): the first attempt always
    # gets the full APEX_BENCH_TIMEOUT (a degraded-but-live run that
    # needs it keeps it, and a healthy run costs nothing extra). Once an
    # attempt TIMES OUT — this relay needed more than the full budget,
    # the §6 wedge/starvation signature — the remaining attempts run
    # under the WEDGE_CAP_S (900s) cap: a healthy retry finishes well
    # under it, a degraded-but-COMPLETE retry still lands as a real
    # rc-0 measurement (the cap covers the observed degraded-attempt
    # envelope), and only the hours a wedged relay would burn are
    # traded away.
    for i in range(attempts):
        cfg_key = json.dumps(ladder[i], sort_keys=True)
        # a config whose measurement is already in hand needn't re-run;
        # re-point flap-retry slots at a still-pending config. Pending is
        # judged against ALL distinct configs (not just the remaining
        # slots): a config whose only slot ran unhealthy gets the spare
        # attempt, whichever slot it originally occupied.
        if cfg_key in healthy_configs:
            pending = [c for c in ladder
                       if json.dumps(c, sort_keys=True)
                       not in healthy_configs]
            if not pending:
                break
            ladder[i] = pending[0]
            cfg_key = json.dumps(ladder[i], sort_keys=True)
        if i:
            if last_outcome == "healthy":
                # previous attempt measured at device speed — the relay
                # is up; jump straight to the next config
                print(f"# attempt {i} healthy; next config "
                      f"({i + 1}/{attempts})", file=sys.stderr, flush=True)
                policy.pop_wait()
            else:
                wait = policy.pop_wait()
                print(f"# attempt {i} was {last_outcome}; retrying in "
                      f"{wait}s ({i + 1}/{attempts})",
                      file=sys.stderr, flush=True)
                time.sleep(wait)
        # attempt beats (ISSUE 16): the watchdog's own stream, so the
        # flight timeline shows attempt boundaries even when the inner
        # child wedges before its first beat
        _flight.beat("attempt_start", attempt=i, config=ladder[i])
        line, rec, rc = _attempt_once(state, ladder[i],
                                      timeout_cap=policy.timeout_cap,
                                      attempt=i)
        _flight.beat("attempt_done", attempt=i, rc=rc,
                     timed_out=bool(rec and rec.get("timed_out")))
        armed = policy.note_attempt(rec, rc)
        if armed:
            # rc None + the fabricated timed_out record = the attempt
            # rode its ENTIRE budget without producing a JSON line
            # (wedge signature) — cap the remaining attempts. Keyed on
            # the structured timed_out stamp, NOT on the presence of an
            # error: a teardown-wedge after printing a real error
            # record (e.g. the calibration-flap line) forwards that
            # record with rc None too, and a completed attempt must
            # never arm the cap (ADVICE r5; the arming rule lives in
            # resilience.RetryPolicy.note_attempt).
            print(f"# wedge signature (timed_out, no JSON inside the "
                  f"budget) — capping remaining attempts at {armed}s",
                  file=sys.stderr, flush=True)
        if rec is not None and rec.get("timed_out") and healthy_configs:
            # window context: a small-working-set config already ran at
            # device speed in these same minutes — this timeout is the
            # §6 SELECTIVE LARGE-HBM STARVATION mode, not a full wedge
            print("# large-HBM starvation signature: small-HBM config "
                  "healthy while this config rode its whole budget "
                  f"(verdict: "
                  f"{resilience.classify(rec, smoke, small_hbm_ok=True)})",
                  file=sys.stderr, flush=True)
        if rec is None:
            # only a crash lands here (the timeout path always
            # fabricates an error record): the child exited with no
            # JSON — deterministic (an import error, traceback already
            # streamed on stderr) or a transient relay-init failure
            # (connection reset instead of a hang). Retry either way,
            # but with a short wait for the NEXT attempt only, so a
            # deterministic crash re-fails in seconds while later
            # non-crash retries keep the full relay-flap backoff
            print(f"# inner bench process crashed (rc={rc}); "
                  f"attempt {i + 1}/{attempts}", file=sys.stderr,
                  flush=True)
            state["crash_rc"] = rc
            last_outcome = "a crash"
            policy.note_crash()
            continue
        value = rec.get("value") or 0
        # a real measurement is one from the requested backend: when a
        # relay flap during backend init silently falls back to the CPU
        # path, that tiny-config smoke number must not be declared the
        # headline (nor value-compared against TPU tokens/s). Smoke mode
        # aside, where CPU is the requested backend.
        requested_backend = _requested_backend(rec, smoke)
        # a clean CPU line on the FIRST attempt (no crash/timeout seen)
        # means a host without TPU hardware — main()'s supported local
        # path — not a mid-flap fallback: accept it as the requested
        # backend so a CPU-only box runs once and exits 0, as before.
        # After any failed attempt the strict rule stands (and the
        # metric label stays an honest "(cpu)" either way).
        if (not requested_backend and i == 0
                and "note" not in rec and "error" not in rec):
            requested_backend = True
            smoke = True  # ok_rc/tiering follow the same acceptance
            # ...and the ladder collapses: a CPU-only box answers no TPU
            # dispatch question, so don't run the whole bench again for
            # a fused-head "A/B" on the wrong backend
            distinct = {cfg_key}
        last_outcome = "relay-bound"
        # best-line ranking (resilience.rank): healthy > degraded
        # (real, tunnel-bound) > implausible calibration artifact —
        # an implausible line's inflated value must never outrank an
        # honest measurement
        rank = resilience.rank(rec, smoke)
        if "error" not in rec and requested_backend and \
                rank > state["best_rank"]:
            state["best"], state["best_rank"] = (line, rec), rank
            # the winning attempt's ladder env rides along so the
            # profiler capture child traces the PROGRAM the headline
            # measured (e.g. the b=16 upside attempt), not the default
            state["best_env"] = ladder[i]
        elif state["best"] is None:
            # last-resort slot: prefer a non-error (cpu-fallback) line
            # over an error line
            prev = state["fallback"]
            if (prev is None or ("error" in prev[1]
                                 and "error" not in rec)):
                state["fallback"] = (line, rec)
        if _healthy_record(rec, smoke):
            last_outcome = "healthy"
            healthy_configs.add(cfg_key)
            if healthy_configs >= distinct:
                break  # every distinct config measured — done
    flush_best()
    # budgeted profiler capture (APEX_PROFILE_CAPTURE=1): strictly after
    # the scored attempts and the flushed line — never on the scored
    # attempt, bounded by its own envelope, refused under a fault plan
    _maybe_profile_capture(state)
    if state["best"] is None and state["fallback"] is None:
        # every attempt crashed or produced nothing: surface the child's
        # exit code as a small honest diagnostic (rc can be negative for
        # a signal-killed child)
        rc = state.get("crash_rc")
        return rc if isinstance(rc, int) and 0 < rc < 128 else 1
    return ok_rc()


if __name__ == "__main__":
    if "--smoke" in sys.argv[1:]:
        # CLI alias for APEX_BENCH_SMOKE=1 (inherited by the watchdog's
        # inner attempts via the environment)
        os.environ["APEX_BENCH_SMOKE"] = "1"
    if "--resume" in sys.argv[1:]:
        # CLI alias for APEX_CKPT_RESUME=1 (inherited the same way):
        # restore the full TrainState from APEX_CKPT_DIR's newest valid
        # checkpoint and continue — the cross-window resume path
        # (PERF.md §6). Requires APEX_CKPT_DIR.
        if not os.environ.get("APEX_CKPT_DIR"):
            print("bench.py --resume requires APEX_CKPT_DIR",
                  file=sys.stderr)
            sys.exit(2)
        os.environ["APEX_CKPT_RESUME"] = "1"
    from apex_tpu.compile_cache import warm_only as _warm_only

    if _warm_only():
        # warm-start pass (benchmarks/warm_cache.py): compile-only, no
        # measurement — the retrying watchdog has nothing to rank
        main()
    elif env_flag("APEX_BENCH_INNER"):
        main()
    else:
        sys.exit(_watchdog())
